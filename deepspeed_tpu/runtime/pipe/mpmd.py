"""MPMD pipeline executor: really executes the 1F1B instruction schedules.

This is the TPU-native counterpart of the reference's instruction-interpreter
pipeline engine (``runtime/pipe/engine.py:37`` with ``_exec_schedule`` at
``:1360`` dispatching ``_INSTRUCTION_MAP``): the :class:`TrainSchedule` /
:class:`InferenceSchedule` command streams from :mod:`.schedule` drive execution
command-by-command. Where the reference interprets on N ranks over NCCL p2p, this
interpreter runs every stage's schedule in lockstep slots inside one process,
with each stage's compute jitted onto its own device and activations moved by
``jax.device_put`` (the single-controller JAX analog of ``SendActivation`` /
``RecvActivation`` — dispatch is async, so neighbor transfers overlap compute
exactly like the reference's p2p streams).

Why this exists next to :func:`.spmd.pipelined_apply` (the compiled
collective-permute pipeline): the SPMD path requires homogeneous stages and pays
GPipe activation residency (all M micro-batch boundary activations live through
the backward). This executor:

- supports **heterogeneous stages** (any :class:`PipelineModule` partition — each
  stage gets its own jitted program);
- achieves true **1F1B memory residency**: a stage holds at most
  ``min(stages - stage_id, micro_batches)`` live activation buffers
  (``TrainSchedule.num_pipe_buffers``, parity ``runtime/pipe/schedule.py:243``) —
  backward recomputes the stage forward from the saved *input* (per-stage remat,
  the reference's ``activation_checkpoint_interval`` discipline), so a "buffer"
  is one stage-input activation;
- reduces tied-weight gradients across their use-site stages at
  ``ReduceTiedGrads`` (parity: ``runtime/pipe/module.py:421``).

Peak residency is tracked per stage (:attr:`MPMDPipelineEngine.peak_live_buffers`)
so tests can assert the 1F1B bound instead of trusting the schedule math.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ...utils.logging import logger
from .module import PipelineModule, TiedLayerSpec
from .schedule import (
    BackwardPass,
    ForwardPass,
    InferenceSchedule,
    LoadMicroBatch,
    OptimizerStep,
    RecvActivation,
    RecvGrad,
    ReduceGrads,
    ReduceTiedGrads,
    SendActivation,
    SendGrad,
    TrainSchedule,
)


def ir_from_train_schedule(num_micro: int, num_stages: int
                           ) -> "ScheduleIR":
    """Lower the executed 1F1B :class:`TrainSchedule` command streams to the
    prover's IR (:mod:`deepspeed_tpu.analysis.schedule`).

    This is the engine's proof obligation: what the prover blesses is a
    faithful rendering of exactly the streams the interpreter runs. Within a
    slot the interpreter executes every stage's sends (phase 1) before any
    recv/compute (phase 2), so each slot flattens as sends, then recvs, then
    compute; channels carry the interpreter's act/grad payloads tagged with
    their micro-batch, which is what the FIFO pairing proof checks.
    """
    from ...analysis.schedule import RECV, SEND, B, F, Instr, ScheduleIR

    stages: List[List[Instr]] = []
    for s in range(num_stages):
        prog: List[Instr] = []
        for cmds in TrainSchedule(num_micro, num_stages, s).steps():
            sends, recvs, compute = [], [], []
            for cmd in cmds:
                m = getattr(cmd, "micro_batch", -1)
                if isinstance(cmd, SendActivation):
                    sends.append(SEND(s + 1, "act", m))
                elif isinstance(cmd, SendGrad):
                    sends.append(SEND(s - 1, "grad", m))
                elif isinstance(cmd, RecvActivation):
                    recvs.append(RECV(s - 1, "act", m))
                elif isinstance(cmd, RecvGrad):
                    recvs.append(RECV(s + 1, "grad", m))
                elif isinstance(cmd, ForwardPass):
                    compute.append(F(m))
                elif isinstance(cmd, BackwardPass):
                    compute.append(B(m))
                # LoadMicroBatch / ReduceGrads / ReduceTiedGrads /
                # OptimizerStep are host-side step bookkeeping, not
                # schedule-ordering instructions
            prog.extend(sends + recvs + compute)
        stages.append(prog)
    return ScheduleIR(name=f"1f1b[m{num_micro},s{num_stages}]",
                      num_stages=num_stages, num_micro=num_micro,
                      stages=stages)


def validate_schedule_pairing(num_micro: int, num_stages: int) -> List[str]:
    """Statically prove the 1F1B command streams are sound (PR 2 contract,
    now a thin shim over the general schedule prover).

    The MPMD interpreter moves activations/grads through per-(stage, micro)
    channels; a schedule whose ``RecvActivation``/``RecvGrad`` fires before
    the matching ``Send`` has run is the single-process rendering of the
    multihost deadlock class. The prover additionally proves global
    deadlock-freedom (acyclic happens-before graph) and weight-version
    consistency. Returns a list of violations (empty = sound); the engine
    refuses to construct on a non-empty list rather than hanging mid-batch.
    """
    from ...analysis.schedule import prove_schedule

    return [f"{f.location}: {f.message}"
            for f in prove_schedule(ir_from_train_schedule(num_micro,
                                                           num_stages))]


# --------------------------------------------------------------------------
# schedule generators: the schedules the prover makes safe to ship
# --------------------------------------------------------------------------
def _list_schedule(num_micro: int, num_stages: int, num_vstages: int = 1,
                   split_backward: bool = False, name: str = ""
                   ) -> "ScheduleIR":
    """Emit a schedule IR by greedy list-scheduling the micro-batch DAG.

    Virtual stage ``v`` (0..V*S-1) lives on physical stage ``v % S``
    (Megatron's interleaved layout). Dependencies: ``F(m, v)`` needs
    ``F(m, v-1)``; ``B(m, v)`` needs ``F(m, v)`` and ``B(m, v+1)``;
    ``W(m, v)`` needs ``B(m, v)``. Each stage runs one instruction at a
    time, preferring B over F over W (B drains activation memory; W is the
    zero-bubble filler that soaks up what would otherwise be idle slots).

    F admission is capped *per virtual stage* at the 1F1B warmup depth
    ``min(V*S - v, M)`` — for V=1 exactly the interpreter's
    ``min(S - s, M)`` buffer bound, and per physical stage the caps sum to
    Megatron's interleaved warmup depth. The cap must be per-chunk: a
    per-physical-stage pool lets shallow-chunk forwards exhaust it and
    starve the deepest chunk's F, which every backward transitively needs —
    a scheduler-induced deadlock. Per-chunk, the last virtual stage's cap is
    ``min(1, M)`` and its B (which B-priority runs next) releases it, so the
    backward chain always originates. Correct by construction — only ready
    work is scheduled — and independently re-proven by the caller.
    """
    import heapq

    from ...analysis.schedule import RECV, SEND, Instr, ScheduleIR

    M, S, V = num_micro, num_stages, num_vstages
    VS = V * S
    t_f = 1.0 / V
    t_b = (1.0 if split_backward else 2.0) / V
    t_w = 1.0 / V
    dur = {"F": t_f, "B": t_b, "W": t_w}
    pri = {"B": 0, "F": 1, "W": 2}
    phys = lambda v: v % S  # noqa: E731

    deps: Dict[Tuple[str, int, int], List[Tuple[str, int, int]]] = {}
    for m in range(M):
        for v in range(VS):
            deps[("F", m, v)] = [("F", m, v - 1)] if v > 0 else []
            deps[("B", m, v)] = [("F", m, v)] + (
                [("B", m, v + 1)] if v < VS - 1 else [])
            if split_backward:
                deps[("W", m, v)] = [("B", m, v)]

    capv = [min(VS - v, M) for v in range(VS)]
    pending = set(deps)
    completed: Dict[Tuple[str, int, int], float] = {}
    prog: List[List[Instr]] = [[] for _ in range(S)]
    stage_busy = [False] * S
    inflight = [0] * VS
    running: List[Tuple[float, int, int, Tuple[str, int, int]]] = []
    seq = 0
    t = 0.0

    def emit_pre(s: int, kind: str, m: int, v: int) -> None:
        if kind == "F" and v > 0 and phys(v - 1) != s:
            prog[s].append(RECV(phys(v - 1), f"act.v{v - 1}", m,
                                vstage=v - 1))
        elif kind == "B" and v < VS - 1 and phys(v + 1) != s:
            prog[s].append(RECV(phys(v + 1), f"grad.v{v + 1}", m,
                                vstage=v + 1))

    def emit_post(s: int, kind: str, m: int, v: int) -> None:
        if kind == "F" and v < VS - 1 and phys(v + 1) != s:
            prog[s].append(SEND(phys(v + 1), f"act.v{v}", m, vstage=v))
        elif kind == "B" and v > 0 and phys(v - 1) != s:
            prog[s].append(SEND(phys(v - 1), f"grad.v{v}", m, vstage=v))

    while pending or running:
        started = True
        while started:
            started = False
            for s in range(S):
                if stage_busy[s]:
                    continue
                ready = [
                    it for it in pending
                    if phys(it[2]) == s
                    and all(d in completed for d in deps[it])
                    and (it[0] != "F" or inflight[it[2]] < capv[it[2]])
                ]
                if not ready:
                    continue
                kind, m, v = min(ready,
                                 key=lambda it: (pri[it[0]], it[1], it[2]))
                pending.discard((kind, m, v))
                emit_pre(s, kind, m, v)
                prog[s].append(Instr(kind, micro=m, vstage=v))
                if kind == "F":
                    inflight[v] += 1
                stage_busy[s] = True
                seq += 1
                heapq.heappush(running, (t + dur[kind], seq, s, (kind, m, v)))
                started = True
        if not running:
            if pending:  # pragma: no cover — the DAG is always serviceable
                raise RuntimeError(f"list scheduler stalled with "
                                   f"{len(pending)} items pending")
            break
        t, _, s, item = heapq.heappop(running)
        completed[item] = t
        stage_busy[s] = False
        kind, m, v = item
        if kind == "B":
            inflight[v] -= 1
        emit_post(s, kind, m, v)

    return ScheduleIR(name=name or f"list[m{M},s{S},v{V}]",
                      num_stages=S, num_micro=M, stages=prog,
                      num_vstages=V)


def generate_1f1b_ir(num_micro: int, num_stages: int) -> "ScheduleIR":
    """The executed 1F1B schedule, in prover IR (lowered from
    :class:`TrainSchedule` — identical to what the interpreter runs)."""
    return ir_from_train_schedule(num_micro, num_stages)


def generate_interleaved_ir(num_micro: int, num_stages: int,
                            num_vstages: int = 2) -> "ScheduleIR":
    """Interleaved virtual stages (Megatron-style closed form): each
    physical stage hosts ``num_vstages`` chunks (virtual stage ``v`` on
    physical ``v % S``), shrinking the warmup/drain bubble to exactly
    ``((S-1)/V) / (M + (S-1)/V)`` of the step — 1/V of 1F1B's — at the cost
    of V× the p2p transfers and a deeper warmup residency. Proven, not yet
    interpreted — the executable engine runs 1F1B; this IR prices and
    proves the upgrade path.

    Per-rank order is the canonical interleaved 1F1B: ``2*(S-s-1) +
    (V-1)*S`` warmup chunk-forwards, then strict F/B alternation, with the
    k-th virtual microbatch mapping to chunk ``(k %% (S*V)) // S`` (reversed
    for backwards) and micro ``(k // (S*V))*S + k %% S`` — which is why
    ``num_micro`` must divide evenly into groups of ``num_stages``.
    """
    M, S, V = num_micro, num_stages, num_vstages
    if V < 2:
        raise ValueError("interleaved schedule needs num_vstages >= 2")
    if M % S != 0:
        raise ValueError(
            f"interleaved schedule needs num_micro ({M}) divisible by "
            f"num_stages ({S}) — the chunk rotation covers micro-batches in "
            f"groups of num_stages")
    from ...analysis.schedule import RECV, SEND, Instr, ScheduleIR

    VS = V * S
    total = M * V
    phys = lambda v: v % S  # noqa: E731

    def f_item(k: int, s: int) -> Tuple[str, int, int]:
        chunk = (k % (S * V)) // S
        return ("F", (k // (S * V)) * S + (k % S), chunk * S + s)

    def b_item(k: int, s: int) -> Tuple[str, int, int]:
        chunk = V - 1 - ((k % (S * V)) // S)
        return ("B", (k // (S * V)) * S + (k % S), chunk * S + s)

    stages: List[List[Instr]] = []
    for s in range(S):
        warmup = min(2 * (S - s - 1) + (V - 1) * S, total)
        order = [f_item(k, s) for k in range(warmup)]
        fk, bk = warmup, 0
        while fk < total:
            order.append(f_item(fk, s))
            fk += 1
            order.append(b_item(bk, s))
            bk += 1
        while bk < total:
            order.append(b_item(bk, s))
            bk += 1
        prog: List[Instr] = []
        for kind, m, v in order:
            if kind == "F" and v > 0 and phys(v - 1) != s:
                prog.append(RECV(phys(v - 1), f"act.v{v - 1}", m,
                                 vstage=v - 1))
            elif kind == "B" and v < VS - 1 and phys(v + 1) != s:
                prog.append(RECV(phys(v + 1), f"grad.v{v + 1}", m,
                                 vstage=v + 1))
            prog.append(Instr(kind, micro=m, vstage=v))
            if kind == "F" and v < VS - 1 and phys(v + 1) != s:
                prog.append(SEND(phys(v + 1), f"act.v{v}", m, vstage=v))
            elif kind == "B" and v > 0 and phys(v - 1) != s:
                prog.append(SEND(phys(v - 1), f"grad.v{v}", m, vstage=v))
        stages.append(prog)
    return ScheduleIR(name=f"interleaved[m{M},s{S},v{V}]",
                      num_stages=S, num_micro=M, stages=stages,
                      num_vstages=V)


def generate_zero_bubble_ir(num_micro: int, num_stages: int
                            ) -> "ScheduleIR":
    """Zero-bubble (ZB-H1-style) schedule: backward split into ``B`` (input
    gradient, on the critical path) and ``W`` (weight gradient, reorderable
    filler). W's are deferred into what 1F1B leaves as drain bubbles, so the
    pipeline's idle fraction drops at *equal* activation residency — the
    scheduler caps in-flight forwards at the same 1F1B warmup depth. Every
    W applies the gradient of its own micro-batch's B; the prover's
    weight-version pass (``pipe/stale-weight-application``) holds the
    generator to that."""
    return _list_schedule(
        num_micro, num_stages, split_backward=True,
        name=f"zero-bubble[m{num_micro},s{num_stages}]")


def _sgd(lr: float):
    """Minimal optax-style transformation used when no optimizer is supplied."""

    def init(params):
        return ()

    def update(grads, state, params=None):
        return jax.tree_util.tree_map(lambda g: -lr * g, grads), state

    return init, update


class MPMDPipelineEngine:
    """Interpret pipeline schedules over per-stage devices.

    Args:
      module: a :class:`PipelineModule` (heterogeneous stages welcome).
      num_micro: micro-batches per ``train_batch`` (M).
      devices: one device per stage (defaults to ``jax.devices()[:S]``; devices
        may repeat when there are fewer devices than stages).
      optimizer: optax ``GradientTransformation`` (or ``(init, update)`` pair)
        applied at ``OptimizerStep``; defaults to SGD(1e-3).
      loss_fn: overrides ``module.loss_fn``; ``loss_fn(last_stage_out, micro_batch)
        -> scalar``.
    """

    def __init__(self, module: PipelineModule, num_micro: int,
                 devices: Optional[Sequence] = None, optimizer=None,
                 loss_fn: Optional[Callable] = None, lr: float = 1e-3,
                 schedule_ir=None):
        self.module = module
        self.S = module.num_stages
        self.M = int(num_micro)
        devs = list(devices) if devices is not None else jax.devices()
        self.devices = [devs[s % len(devs)] for s in range(self.S)]
        self.loss_fn = loss_fn or module.loss_fn
        if self.loss_fn is None:
            raise ValueError("MPMDPipelineEngine needs a loss_fn")
        if optimizer is None:
            self._opt_init, self._opt_update = _sgd(lr)
        elif isinstance(optimizer, tuple):
            self._opt_init, self._opt_update = optimizer
        else:  # optax GradientTransformation
            self._opt_init, self._opt_update = optimizer.init, optimizer.update

        # the proof obligation: the schedule the interpreter will run (or an
        # explicit override under test/experiment), proven BEFORE any stage
        # program is built or dispatched — the engine refuses a rejected
        # schedule rather than hanging mid-batch
        from ...analysis.schedule import prove_schedule

        self.schedule_ir = (schedule_ir if schedule_ir is not None
                            else ir_from_train_schedule(self.M, self.S))
        findings = prove_schedule(self.schedule_ir)
        if findings:
            raise ValueError(
                f"pipeline schedule {self.schedule_ir.name!r} rejected by "
                "the static prover (would deadlock or corrupt gradients in "
                "a multi-process run):\n  "
                + "\n  ".join(f"{f.rule_id}: {f.location}: {f.message}"
                              for f in findings))

        self._stage_fns = [self._make_stage_fn(s) for s in range(self.S)]
        self._fwd_jit: List[Callable] = []
        self._bwd_jit: List[Callable] = []
        self._infer_jit: List[Callable] = []
        for s in range(self.S):
            self._fwd_jit.append(jax.jit(self._stage_fwd(s)))
            self._bwd_jit.append(jax.jit(self._stage_bwd(s)))
            self._infer_jit.append(jax.jit(self._stage_fns[s]))
        self.peak_live_buffers = [0] * self.S
        self.timers: Dict[str, float] = {}

    # ------------------------------------------------------------ stage programs
    def _make_stage_fn(self, s: int) -> Callable:
        lo, hi = self.module.parts[s], self.module.parts[s + 1]
        specs = self.module.specs

        def fn(stage_params, tied, x):
            for i in range(lo, hi):
                spec = specs[i]
                w = tied[spec.key] if isinstance(spec, TiedLayerSpec) \
                    else stage_params[i - lo]
                x = spec.apply(w, x)
            return x

        return fn

    def _stage_fwd(self, s: int) -> Callable:
        fn = self._stage_fns[s]
        if s == self.S - 1:
            loss_fn = self.loss_fn

            def fwd(stage_params, tied, x, micro_batch):
                return loss_fn(fn(stage_params, tied, x), micro_batch)

            return fwd
        return fn

    def _stage_bwd(self, s: int) -> Callable:
        """Recompute-forward VJP: consumes the saved stage *input* (the 1F1B
        buffer) + upstream grad, returns (dparams, dtied, dx)."""
        fn = self._stage_fns[s]
        if s == self.S - 1:
            loss_fn = self.loss_fn

            def bwd(stage_params, tied, x, micro_batch, scale):
                def f(p, t, x):
                    return loss_fn(fn(p, t, x), micro_batch)

                _, vjp = jax.vjp(f, stage_params, tied, x)
                return vjp(scale)

            return bwd

        def bwd(stage_params, tied, x, g):
            _, vjp = jax.vjp(fn, stage_params, tied, x)
            return vjp(g)

        return bwd

    # ------------------------------------------------------------ params
    def init(self, rng) -> Dict[str, Any]:
        """Build params placed stage-by-stage on their devices:
        ``{"stages": [per-stage layer lists], "tied": {key: ...}}`` (tied weights
        live on their first use-site's device and are mirrored on use)."""
        full = self.module.init(rng)
        stages = []
        for s in range(self.S):
            lo, hi = self.module.parts[s], self.module.parts[s + 1]
            stages.append(jax.device_put(full["layers"][lo:hi], self.devices[s]))
        tied = jax.device_put(full["tied"], self.devices[0])
        return {"stages": stages, "tied": tied}

    def init_optimizer(self, params) -> Any:
        return self._opt_init(params)

    # ------------------------------------------------------------ train
    def train_batch(self, params, opt_state, batch,
                    apply_update: bool = True) -> Tuple[Any, Any, Dict[str, Any]]:
        """Run one 1F1B-scheduled training step over ``self.M`` micro-batches.

        ``batch`` is a pytree of ``[M, mb, ...]`` leaves (see
        :func:`.spmd.split_microbatches`). Returns ``(params, opt_state, metrics)``
        with ``metrics["loss"]`` the micro-mean loss and ``metrics["grads"]`` the
        full gradient tree (for tests / external reduction).
        """
        S, M = self.S, self.M
        scheds = [TrainSchedule(M, S, s) for s in range(S)]
        streams = [list(sched.steps()) for sched in scheds]
        n_slots = len(streams[0])

        def micro_batch(m):
            return jax.tree_util.tree_map(lambda leaf: leaf[m], batch)

        # live state ------------------------------------------------------------
        inputs: List[Dict[int, Any]] = [{} for _ in range(S)]   # micro -> stage input
        outputs: List[Dict[int, Any]] = [{} for _ in range(S)]  # micro -> stage output
        act_ch: Dict[Tuple[int, int], Any] = {}   # (dst_stage, micro) -> activation
        grad_ch: Dict[Tuple[int, int], Any] = {}  # (dst_stage, micro) -> grad
        dx_out: List[Dict[int, Any]] = [{} for _ in range(S)]   # micro -> dx to send
        grad_acc = [None] * S
        tied_acc = [None] * S
        losses = []
        live_peak = [0] * S
        scale = jnp.float32(1.0 / M)

        def acc(tree_a, tree_b):
            if tree_a is None:
                return tree_b
            return jax.tree_util.tree_map(jnp.add, tree_a, tree_b)

        stage_params = params["stages"]
        tied = params["tied"]
        tied_per_stage = [jax.device_put(tied, self.devices[s]) for s in range(S)]

        done = {"step": False}
        for t in range(n_slots):
            # phase 1: sends (depend only on prior slots' compute); each Send
            # carries its micro-batch id (set by the schedule), so no slot-
            # parity inference is needed
            for s in range(S):
                for cmd in streams[s][t]:
                    if isinstance(cmd, SendActivation):
                        m = cmd.micro_batch
                        act_ch[(s + 1, m)] = jax.device_put(
                            outputs[s].pop(m), self.devices[s + 1])
                    elif isinstance(cmd, SendGrad):
                        m = cmd.micro_batch
                        grad_ch[(s - 1, m)] = jax.device_put(
                            dx_out[s].pop(m), self.devices[s - 1])
            # phase 2: loads, recvs, compute
            for s in range(S):
                for cmd in streams[s][t]:
                    m = getattr(cmd, "micro_batch", -1)
                    if isinstance(cmd, LoadMicroBatch):
                        mb = micro_batch(m)
                        x = mb["input_ids"] if isinstance(mb, dict) else mb
                        inputs[s][m] = jax.device_put(x, self.devices[s])
                    elif isinstance(cmd, RecvActivation):
                        inputs[s][m] = act_ch.pop((s, m))
                    elif isinstance(cmd, RecvGrad):
                        # the matching SendGrad ran in phase 1 of this very slot
                        # (stage s+1's send and stage s's backward share a slot)
                        assert (s, m) in grad_ch, f"grad for micro {m} not sent"
                    elif isinstance(cmd, ForwardPass):
                        live_peak[s] = max(live_peak[s], len(inputs[s]))
                        if s == S - 1:
                            loss = self._fwd_jit[s](
                                stage_params[s], tied_per_stage[s],
                                inputs[s][m], micro_batch(m))
                            losses.append(loss)
                        else:
                            outputs[s][m] = self._fwd_jit[s](
                                stage_params[s], tied_per_stage[s], inputs[s][m])
                    elif isinstance(cmd, BackwardPass):
                        if s == S - 1:
                            dp, dt, dx = self._bwd_jit[s](
                                stage_params[s], tied_per_stage[s],
                                inputs[s].pop(m), micro_batch(m), scale)
                        else:
                            g = grad_ch.pop((s, m))
                            dp, dt, dx = self._bwd_jit[s](
                                stage_params[s], tied_per_stage[s],
                                inputs[s].pop(m), g)
                        grad_acc[s] = acc(grad_acc[s], dp)
                        tied_acc[s] = acc(tied_acc[s], dt)
                        if s > 0:
                            dx_out[s][m] = dx
                    elif isinstance(cmd, ReduceTiedGrads):
                        pass  # handled once below, after the slot loop ordering
                    elif isinstance(cmd, (ReduceGrads, OptimizerStep)):
                        done["step"] = True

        # ReduceTiedGrads: sum tied-grad contributions across stages onto stage-0's
        # device (parity: tied allreduce, runtime/pipe/module.py:421)
        tied_grads = None
        for s in range(S):
            if tied_acc[s] is not None:
                tied_grads = acc(tied_grads, jax.device_put(
                    tied_acc[s], self.devices[0]))
        grads = {"stages": grad_acc, "tied": tied_grads}
        self.peak_live_buffers = live_peak

        metrics = {
            "loss": jnp.mean(jnp.stack([jax.device_put(l, self.devices[-1])
                                        for l in losses])),
            "grads": grads,
        }
        if apply_update and done["step"]:
            params, opt_state = self._apply_update(params, grads, opt_state)
        return params, opt_state, metrics

    def _apply_update(self, params, grads, opt_state):
        flat_p = {"stages": params["stages"], "tied": params["tied"]}
        updates, opt_state = self._opt_update(grads, opt_state, flat_p)
        new_stages = [
            jax.tree_util.tree_map(jnp.add, params["stages"][s],
                                   updates["stages"][s])
            for s in range(self.S)
        ]
        new_tied = (jax.tree_util.tree_map(jnp.add, params["tied"], updates["tied"])
                    if updates["tied"] is not None else params["tied"])
        return {"stages": new_stages, "tied": new_tied}, opt_state

    # ------------------------------------------------------------ inference
    def forward_batch(self, params, batch) -> jnp.ndarray:
        """Forward-only pipelining driven by :class:`InferenceSchedule`; returns
        the last stage's outputs stacked ``[M, ...]``."""
        S, M = self.S, self.M
        streams = [list(InferenceSchedule(M, S, s).steps()) for s in range(S)]
        act_ch: Dict[Tuple[int, int], Any] = {}
        inputs: List[Dict[int, Any]] = [{} for _ in range(S)]
        outputs: List[Dict[int, Any]] = [{} for _ in range(S)]
        outs: Dict[int, Any] = {}
        stage_params, tied = params["stages"], params["tied"]
        tied_per_stage = [jax.device_put(tied, self.devices[s]) for s in range(S)]

        def micro_batch(m):
            return jax.tree_util.tree_map(lambda leaf: leaf[m], batch)

        n_slots = len(streams[0])
        for t in range(n_slots):
            for s in reversed(range(S)):  # sends precede the recv one slot later
                for cmd in streams[s][t]:
                    m = cmd.micro_batch
                    if isinstance(cmd, LoadMicroBatch):
                        mb = micro_batch(m)
                        x = mb["input_ids"] if isinstance(mb, dict) else mb
                        inputs[s][m] = jax.device_put(x, self.devices[s])
                    elif isinstance(cmd, RecvActivation):
                        inputs[s][m] = act_ch.pop((s, m))
                    elif isinstance(cmd, ForwardPass):
                        y = self._infer_jit[s](stage_params[s], tied_per_stage[s],
                                               inputs[s].pop(m))
                        if s == S - 1:
                            outs[m] = y
                        else:
                            outputs[s][m] = y
                    elif isinstance(cmd, SendActivation):
                        act_ch[(s + 1, m)] = jax.device_put(
                            outputs[s].pop(m), self.devices[s + 1])
        return jnp.stack([outs[m] for m in range(M)])
