"""SPMD pipeline-parallel executor.

The TPU-native replacement for the reference's instruction-interpreter pipeline
engine (``runtime/pipe/engine.py:37,1360``: dispatch of Send/Recv/Forward/Backward
commands over NCCL p2p with hand-managed buffers and a separate grad pipeline).

Design (collective-permute pipelining inside one XLA program):

- stage weights live stacked on a leading ``[S, ...]`` axis sharded over the ``pp``
  mesh axis — each device holds only its stage's layers;
- the activation "buffers" are one ``[S, micro_batch, ...]`` array, also
  pp-sharded: row ``i`` is what stage ``i`` is currently processing;
- one *tick* applies every stage to its row in parallel (``vmap`` over the stage
  axis — pure per-row compute, so XLA keeps each row on its shard) and then shifts
  rows down by one (``concatenate([new_input, y[:-1]])`` on a pp-sharded axis
  lowers to a neighbor collective-permute — exactly the reference's
  ``SendActivation``/``RecvActivation`` pair, scheduled by the compiler);
- after ``M + S - 1`` ticks every micro-batch has exited the last stage
  (GPipe-style fill/drain: the (S-1)/(M+S-1) bubble is identical to the
  reference's 1F1B bubble);
- **backward**: ``jax.grad`` of this loop. The transpose of a collective-permute
  is the reverse permute, so autodiff yields the mirrored grad pipeline
  (``SendGrad``/``RecvGrad``) with no extra code. Per-tick ``jax.checkpoint``
  bounds activation memory to one stage-activation per in-flight micro-batch —
  the same residency 1F1B achieves.

Tied weights (embedding read at stage 0, head at stage S-1) are handled by keeping
them *outside* the pipelined scan (replicated over pp); autodiff sums both use
sites' contributions, replacing the reference's explicit tied-grad allreduce
(``runtime/pipe/module.py:421``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...models.api import maybe_shard


def pipelined_apply(
    stage_fn: Callable[..., jnp.ndarray],
    stage_params: Any,
    microbatches: jnp.ndarray,
    num_stages: int,
    *,
    stream_spec: Optional[P] = None,
    remat: bool = True,
    extra_args: Tuple = (),
) -> jnp.ndarray:
    """Run ``microbatches`` through ``num_stages`` pipeline stages.

    Args:
      stage_fn: ``(params_slice, x, micro_id, stage_id, *extra) -> y`` — one
        stage's compute for ONE micro-batch. ``params_slice`` is the per-stage
        leaf slice (leading stage axis removed by the vmap), ``micro_id`` the
        micro-batch index and ``stage_id`` the stage index (for rng folding /
        global layer ids); must be shape-preserving on ``x`` (stages are
        homogeneous — the transformer case; heterogeneous stacks use
        PipelineModule.apply).
      stage_params: pytree with leading ``[S, ...]`` stage axis on every leaf,
        sharded ``P("pp", ...)``.
      microbatches: ``[M, mb, ...]`` activation stream entering stage 0.
      num_stages: S; must equal the ``pp`` mesh-axis size when sharded.
      stream_spec: PartitionSpec of ONE micro-batch (e.g. ``P(("dp","ep"), "sp",
        None)``) used to constrain the rotating buffer's tail dims.
      remat: rematerialize each tick (activation checkpointing over the pipeline).
      extra_args: broadcast to every stage invocation (e.g. positions).

    Returns ``[M, mb, ...]`` outputs of the last stage (valid for all M).
    """
    S = int(num_stages)
    M = int(microbatches.shape[0])
    tail = stream_spec if stream_spec is not None else P()
    buf_spec = P("pp", *tuple(tail))

    def one_stage(w, x, micro_id, stage_id, *extra):
        return stage_fn(w, x, micro_id, stage_id, *extra)

    vstage = jax.vmap(one_stage, in_axes=(0, 0, 0, 0, *([None] * len(extra_args))))
    if remat:
        vstage = jax.checkpoint(vstage)

    # stage i at tick t processes micro-batch (t - i); negative/overflow ids are
    # bubble ticks whose output never lands in `outputs`.
    stage_ids = jnp.arange(S)

    def tick(carry, t):
        state, outputs = carry
        micro_ids = t - stage_ids  # [S]
        y = vstage(stage_params, state, micro_ids, stage_ids, *extra_args)
        y = maybe_shard(y, buf_spec)
        # last stage's output is micro-batch t-(S-1); clamp → early garbage lands
        # in slot 0 and is overwritten at t = S-1 when the real one arrives.
        out_idx = jnp.clip(t - (S - 1), 0, M - 1)
        outputs = jax.lax.dynamic_update_slice_in_dim(outputs, y[-1:], out_idx, axis=0)
        # shift: stage 0 ingests the next micro-batch, stage i takes stage i-1's out
        nxt = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t + 1, 0, M - 1), axis=0, keepdims=True)
        state = jnp.concatenate([nxt, y[:-1]], axis=0)
        state = maybe_shard(state, buf_spec)
        return (state, outputs), None

    mb_shape = microbatches.shape[1:]
    state0 = jnp.concatenate(
        [microbatches[0][None],
         jnp.zeros((S - 1,) + mb_shape, microbatches.dtype)], axis=0)
    state0 = maybe_shard(state0, buf_spec)
    outputs0 = jnp.zeros((M,) + mb_shape, microbatches.dtype)

    (_, outputs), _ = jax.lax.scan(
        tick, (state0, outputs0), jnp.arange(M + S - 1))
    return outputs


def stack_stage_params(layer_params: Any, num_stages: int) -> Any:
    """Reshape stacked-layer leaves ``[L, ...]`` -> ``[S, L/S, ...]`` so the
    leading axis is the pipeline-stage axis. Parity: the reference's
    ``PipelineModule._partition_layers`` uniform split (``runtime/pipe/module.py:365``)
    for homogeneous stacks."""

    def reshape(leaf):
        L = leaf.shape[0]
        if L % num_stages != 0:
            raise ValueError(
                f"layer count {L} not divisible by pipeline stages {num_stages}")
        return leaf.reshape((num_stages, L // num_stages) + leaf.shape[1:])

    return jax.tree_util.tree_map(reshape, layer_params)


def unstack_stage_params(stage_params: Any) -> Any:
    """Inverse of :func:`stack_stage_params`."""
    return jax.tree_util.tree_map(
        lambda leaf: leaf.reshape((leaf.shape[0] * leaf.shape[1],) + leaf.shape[2:]),
        stage_params)


def split_microbatches(batch: Any, num_micro: int) -> Any:
    """Reshape each [B, ...] leaf to [M, B/M, ...]."""

    def reshape(leaf):
        B = leaf.shape[0]
        if B % num_micro != 0:
            raise ValueError(f"batch {B} not divisible by micro-batches {num_micro}")
        return leaf.reshape((num_micro, B // num_micro) + leaf.shape[1:])

    return jax.tree_util.tree_map(reshape, batch)


def merge_microbatches(batch: Any) -> Any:
    """Inverse of :func:`split_microbatches`."""
    return jax.tree_util.tree_map(
        lambda leaf: leaf.reshape((leaf.shape[0] * leaf.shape[1],) + leaf.shape[2:]),
        batch)
