"""Pipeline model description: layer specs and stage partitioning.

Capability parity with the reference's ``runtime/pipe/module.py`` (``LayerSpec:24``,
``TiedLayerSpec:68``, ``PipelineModule:86`` with stage partitioning ``_partition_layers
:365`` using ``partition_uniform``/``partition_balanced`` from ``runtime/utils.py``).

TPU-native shape: a ``LayerSpec`` carries pure functions (init, apply) instead of a
torch class; ``PipelineModule`` assigns layers to ``num_stages`` pipeline stages and
produces a functional :class:`~deepspeed_tpu.models.api.Module`. Execution:

- ``pp == 1`` or heterogeneous stages: layers run sequentially in one program (the
  partitioning still matters for activation-checkpoint granularity).
- homogeneous stacked stages (the transformer case): the SPMD executor in
  :mod:`.spmd` pipelines micro-batches over the ``pp`` mesh axis with
  collective-permutes; tied weights (``TiedLayerSpec``) need no special grad
  allreduce — autodiff sums the contributions of every use site (the reference
  does this by hand at ``runtime/pipe/module.py:421``).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ...models.api import Module
from ...utils.logging import logger


class LayerSpec:
    """Deferred layer: built per-stage so no stage materializes other stages'
    params. Parity: ``runtime/pipe/module.py:24``.

    ``init(rng) -> params`` and ``apply(params, x, **kw) -> y``; ``param_count``
    lets ``partition_method="parameters"`` balance stages without materializing.
    """

    def __init__(self, init: Callable, apply: Callable, name: str = "layer",
                 param_count: int = 0):
        self.init = init
        self.apply = apply
        self.name = name
        self.param_count = int(param_count)

    def build(self, rng) -> Any:
        return self.init(rng)

    def __repr__(self):
        return f"LayerSpec({self.name}, params={self.param_count})"


class TiedLayerSpec(LayerSpec):
    """A layer whose parameters are shared with every other TiedLayerSpec of the
    same ``key`` (e.g. embedding/unembedding). Parity: ``runtime/pipe/module.py:68``.
    Tied params are stored once in the param tree under ``tied/<key>``."""

    def __init__(self, key: str, init: Callable, apply: Callable, name: str = "tied",
                 param_count: int = 0):
        super().__init__(init, apply, name=name, param_count=param_count)
        self.key = key

    def __repr__(self):
        return f"TiedLayerSpec({self.key}, params={self.param_count})"


# ----------------------------------------------------------------- partitioning
def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Boundaries assigning ``num_items`` into ``num_parts`` near-equal contiguous
    ranges. Parity: ``runtime/utils.py`` ``partition_uniform``. Returns
    ``num_parts+1`` boundaries."""
    parts = [0] * (num_parts + 1)
    chunk, residual = divmod(num_items, num_parts)
    for p in range(num_parts):
        parts[p + 1] = parts[p] + chunk + (1 if p < residual else 0)
    return parts

def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Contiguous partition of ``weights`` minimizing the max part weight
    (binary search over the bottleneck + greedy check). Parity:
    ``runtime/utils.py`` ``partition_balanced`` (reference uses the same
    prefix-sum + bisection idea)."""
    weights = [float(w) for w in weights]
    n = len(weights)
    if num_parts >= n:
        # one item per part (plus empty tail parts)
        return partition_uniform(n, num_parts)
    prefix = np.concatenate([[0.0], np.cumsum(weights)])

    def can(limit: float) -> Optional[List[int]]:
        bounds = [0]
        start = 0
        for _ in range(num_parts):
            # furthest end such that sum(start:end) <= limit
            hi = int(np.searchsorted(prefix, prefix[start] + limit, side="right")) - 1
            if hi <= start:
                return None  # single item exceeds limit
            hi = min(hi, n)
            bounds.append(hi)
            start = hi
            if hi == n:
                break
        if bounds[-1] != n:
            return None
        while len(bounds) < num_parts + 1:
            bounds.append(n)
        return bounds

    lo = max(weights) if weights else 0.0
    hi = float(prefix[-1])
    best = can(hi)
    for _ in range(50):
        mid = (lo + hi) / 2
        b = can(mid)
        if b is not None:
            best, hi = b, mid
        else:
            lo = mid
    assert best is not None
    return best


class PipelineModule:
    """Partition a layer list over pipeline stages; build per-stage params.

    Parity: ``runtime/pipe/module.py:86``. ``partition_method``:
    - ``"uniform"``: equal layer counts;
    - ``"parameters"``: balance by per-layer param counts;
    - ``"type:<regex>"``: balance count of layers whose name matches.
    """

    def __init__(self, layers: Sequence[LayerSpec], num_stages: int,
                 partition_method: str = "parameters",
                 loss_fn: Optional[Callable] = None,
                 activation_checkpoint_interval: int = 0):
        self.specs = list(layers)
        self.num_stages = int(num_stages)
        self.partition_method = partition_method
        self.loss_fn = loss_fn
        self.activation_checkpoint_interval = int(activation_checkpoint_interval)
        self.parts = self._partition_layers()
        logger.info(f"PipelineModule: {len(self.specs)} layers -> {self.num_stages} "
                    f"stages at bounds {self.parts}")

    # ------------------------------------------------------------ partitioning
    def _partition_layers(self) -> List[int]:
        method = self.partition_method.lower()
        n = len(self.specs)
        if method == "uniform":
            return partition_uniform(n, self.num_stages)
        if method == "parameters":
            weights = [max(1, s.param_count) for s in self.specs]
            return partition_balanced(weights, self.num_stages)
        if method.startswith("type:"):
            pattern = method.split(":", 1)[1]
            weights = [1 if re.search(pattern, s.name, re.IGNORECASE) else 0
                       for s in self.specs]
            if sum(weights) == 0:
                raise ValueError(f"no layer names match partition regex {pattern!r}")
            return partition_balanced([w + 1e-3 for w in weights], self.num_stages)
        raise NotImplementedError(f"partition_method {self.partition_method!r}")

    def stage_layers(self, stage_id: int) -> List[LayerSpec]:
        return self.specs[self.parts[stage_id]:self.parts[stage_id + 1]]

    def stage_of_layer(self, layer_idx: int) -> int:
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        raise IndexError(layer_idx)

    @property
    def tied_keys(self) -> List[str]:
        keys = []
        for s in self.specs:
            if isinstance(s, TiedLayerSpec) and s.key not in keys:
                keys.append(s.key)
        return keys

    # ------------------------------------------------------------ functional build
    def init(self, rng) -> Dict[str, Any]:
        """Build the full param tree: ``{"layers": [per-layer], "tied": {key: ...}}``.
        Tied keys are built once (first spec wins)."""
        params: Dict[str, Any] = {"layers": [], "tied": {}}
        rngs = jax.random.split(rng, len(self.specs) + 1)
        for i, spec in enumerate(self.specs):
            if isinstance(spec, TiedLayerSpec):
                if spec.key not in params["tied"]:
                    params["tied"][spec.key] = spec.build(rngs[i])
                params["layers"].append({})  # weights live under tied/
            else:
                params["layers"].append(spec.build(rngs[i]))
        return params

    def apply(self, params, x, **kw):
        """Sequential execution through all layers (single-program path; also the
        reference semantics for ``pp=1``). With
        ``activation_checkpoint_interval>0``, each interval chunk is rematerialized
        (parity: ``runtime/pipe/module.py:309-364`` forward with checkpointing)."""
        interval = self.activation_checkpoint_interval

        def run_range(x, lo, hi):
            for i in range(lo, hi):
                spec = self.specs[i]
                w = (params["tied"][spec.key]
                     if isinstance(spec, TiedLayerSpec) else params["layers"][i])
                x = spec.apply(w, x, **kw)
            return x

        if interval <= 0:
            return run_range(x, 0, len(self.specs))
        # honors the globally-configured activation-checkpointing options
        # (partition_activations / cpu_checkpointing / policy)
        from ..activation_checkpointing import checkpoint_wrapper

        i = 0
        while i < len(self.specs):
            hi = min(i + interval, len(self.specs))
            x = checkpoint_wrapper(lambda x, lo=i, hi=hi: run_range(x, lo, hi))(x)
            i = hi
        return x

    def to_module(self, partition_specs: Optional[Callable] = None) -> Module:
        """Wrap as an engine-consumable :class:`Module`; ``apply`` feeds the last
        layer's output to ``loss_fn(output, batch)`` when provided."""

        def apply(params, batch, rngs=None, train=True):
            x = batch["input_ids"] if isinstance(batch, dict) else batch
            out = self.apply(params, x)
            if self.loss_fn is not None:
                loss = self.loss_fn(out, batch)
            else:
                loss = out
            return loss, {}

        return Module(init=self.init, apply=apply, partition_specs=partition_specs)
