"""Pipeline parallelism. Parity: reference ``deepspeed/runtime/pipe/``."""

from .module import (LayerSpec, PipelineModule, TiedLayerSpec,  # noqa: F401
                     partition_balanced, partition_uniform)
from .schedule import (DataParallelSchedule, InferenceSchedule,  # noqa: F401
                       PipeSchedule, TrainSchedule, bubble_fraction)
from .spmd import (merge_microbatches, pipelined_apply,  # noqa: F401
                   split_microbatches, stack_stage_params, unstack_stage_params)
from .mpmd import MPMDPipelineEngine  # noqa: F401
