"""NVMe swapping of optimizer state (ZeRO-Infinity).

Capability parity with the reference's swap_tensor stack
(``runtime/swap_tensor/partitioned_optimizer_swapper.py:27`` and the pipelined
variant ``pipelined_optimizer_swapper.py:32``): optimizer-state tensors live on
local SSD, and the optimizer loop overlaps the current leaf's compute with the
next leaf's async read and the previous leaf's async write-back, via the native
thread-pool AIO library (:mod:`deepspeed_tpu.ops.aio`, ``csrc/aio.cpp``).

Host RAM holds only a window of leaves (the reference's ``buffer_count``), so the
optimizer footprint is O(window), with the full state on disk — the
ZeRO-Infinity memory story on a TPU VM's local SSD.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...ops.aio import AsyncIOHandle
from ...utils.logging import log_dist

_STREAMS = ("master", "m", "v")


class NVMeLeafStore:
    """Per-leaf (master, m, v) triples on disk with pipelined prefetch."""

    def __init__(self, path: str, aio_threads: int = 4):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.aio = AsyncIOHandle(num_threads=aio_threads)
        self.shapes: List[Tuple[int, ...]] = []
        # leaf index -> {stream: (buffer, request_id)}
        self._inflight_reads: Dict[int, Dict[str, Tuple[np.ndarray, int]]] = {}
        # buffers being written back; must stay alive until drain
        self._inflight_writes: List[np.ndarray] = []
        log_dist(f"NVMe optimizer store at {path} "
                 f"({'native aio' if self.aio.is_native else 'sync fallback'})")

    def _file(self, i: int, stream: str) -> str:
        return os.path.join(self.path, f"leaf_{i}_{stream}.bin")

    # ------------------------------------------------------------------ init
    def write_init(self, leaves: List[np.ndarray]) -> None:
        """Write initial (master, zeros, zeros) for every leaf; blocking."""
        self.shapes = [l.shape for l in leaves]
        zeros_pool: Dict[Tuple[int, ...], np.ndarray] = {}
        for i, master in enumerate(leaves):
            rid = self.aio.pwrite(self._file(i, "master"),
                                  np.ascontiguousarray(master, np.float32))
            self.aio.wait(rid)
            z = zeros_pool.setdefault(master.shape,
                                      np.zeros(master.shape, np.float32))
            for stream in ("m", "v"):
                rid = self.aio.pwrite(self._file(i, stream), z)
                self.aio.wait(rid)

    @property
    def num_leaves(self) -> int:
        return len(self.shapes)

    # ------------------------------------------------------------------ pipelined access
    def prefetch(self, i: int) -> None:
        """Kick off async reads of leaf ``i``'s three streams."""
        if i in self._inflight_reads or not (0 <= i < self.num_leaves):
            return
        entry = {}
        for stream in _STREAMS:
            buf = np.empty(self.shapes[i], np.float32)
            rid = self.aio.pread(self._file(i, stream), buf)
            entry[stream] = (buf, rid)
        self._inflight_reads[i] = entry

    def get(self, i: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Blocking: returns leaf ``i``'s (master, m, v), prefetched or not."""
        self.prefetch(i)
        entry = self._inflight_reads.pop(i)
        out = []
        for stream in _STREAMS:
            buf, rid = entry[stream]
            rc = self.aio.wait(rid)
            if rc != 0:
                raise IOError(f"aio read failed for leaf {i}/{stream}: {rc}")
            out.append(buf)
        return tuple(out)

    def writeback(self, i: int, master: np.ndarray, m: np.ndarray,
                  v: np.ndarray) -> None:
        """Async write-back; buffers are retained until :meth:`drain`."""
        for stream, buf in zip(_STREAMS, (master, m, v)):
            self.aio.pwrite(self._file(i, stream), buf)
            self._inflight_writes.append(buf)

    def drain(self) -> None:
        self.aio.drain()
        self._inflight_writes.clear()

    # ------------------------------------------------------------------ checkpoint
    def read_all(self) -> Dict[str, np.ndarray]:
        self.drain()
        out = {}
        for i in range(self.num_leaves):
            master, m, v = self.get(i)
            out[f"master_{i}"] = master
            out[f"m_{i}"] = m
            out[f"v_{i}"] = v
        return out

    def write_all(self, d: Dict[str, np.ndarray]) -> None:
        self.drain()
        for i in range(self.num_leaves):
            self.writeback(i, np.ascontiguousarray(d[f"master_{i}"], np.float32),
                           np.ascontiguousarray(d[f"m_{i}"], np.float32),
                           np.ascontiguousarray(d[f"v_{i}"], np.float32))
        self.drain()
