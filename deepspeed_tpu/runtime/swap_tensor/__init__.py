from .optimizer_swapper import NVMeLeafStore  # noqa: F401
