"""Activation checkpointing (rematerialization), TPU-native.

Capability parity with the reference's Megatron-derived module
(``runtime/activation_checkpointing/checkpointing.py``): the ``checkpoint(fn, *args)``
entry point (``:748``), global ``configure(...)`` from the DeepSpeed JSON block
(``:830``), activation *partitioning* across model-parallel ranks (``:372``),
CPU checkpointing (host offload of saved activations), and the RNG-state tracker
(``CudaRNGStatesTracker``, ``:122``).

TPU-native design — each reference mechanism maps to a compiler facility instead of
hand-managed buffers:

- recompute-in-backward  -> ``jax.checkpoint`` (XLA rematerialization). No custom
  autograd Function, no stashed tensors: the saved-residual set is a *policy*.
- ``partition_activations`` -> saved residuals are sharding-constrained over the
  model-parallel axes (tp, sp), so each rank stores ``1/mp`` of every checkpoint —
  the same memory math as the reference's scatter/gather, but the "gather" at
  recompute time is an XLA all-gather it schedules and overlaps itself.
- ``cpu_checkpointing`` -> ``jax.checkpoint`` offload policies: residuals are moved
  to ``pinned_host`` memory between fwd and bwd (``save_and_offload_only_these_names``
  machinery via ``jax.checkpoint_policies.offload_*``).
- ``contiguous_memory_optimization`` -> no-op by construction: XLA allocates saved
  residuals in one arena; there is no fragmentation to manage. Accepted, ignored.
- RNG tracker -> JAX PRNG keys are explicit values, so recompute determinism is
  automatic (the same key is an input to both executions). The tracker here exists
  for API parity and for deriving *model-parallel-unique* dropout keys the way the
  reference seeds each MP rank differently (``:122-258``).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from ...utils.logging import logger

# must match the policy actually built in policy_from_config
_OFFLOAD_SUPPORTED = hasattr(jax.checkpoint_policies, "offload_dot_with_no_batch_dims")


@dataclasses.dataclass
class CheckpointConfig:
    """Resolved knobs. Parity: module-level globals set by ``configure`` (``:830``)."""

    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # jax-side selection of what to save when NOT recomputing everything
    policy_name: str = "nothing_saveable"
    mp_axes: Sequence[str] = ("tp", "sp")


_config = CheckpointConfig()
_configured = False


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None) -> None:
    """Parity: ``checkpointing.configure`` (``:830``) — same signature shape; accepts
    either the parsed DeepSpeed config or explicit overrides."""
    global _config, _configured
    cfg = CheckpointConfig()
    if deepspeed_config is not None:
        block = getattr(deepspeed_config, "activation_checkpointing", None)
        if block is not None:
            cfg.partition_activations = block.partition_activations
            cfg.cpu_checkpointing = block.cpu_checkpointing
            cfg.contiguous_memory_optimization = block.contiguous_memory_optimization
            cfg.number_checkpoints = block.number_checkpoints
            cfg.synchronize_checkpoint_boundary = block.synchronize_checkpoint_boundary
            cfg.profile = block.profile
    if partition_activations is not None:
        cfg.partition_activations = partition_activations
    if contiguous_checkpointing is not None:
        cfg.contiguous_memory_optimization = contiguous_checkpointing
    if num_checkpoints is not None:
        cfg.number_checkpoints = num_checkpoints
    if checkpoint_in_cpu is not None:
        cfg.cpu_checkpointing = checkpoint_in_cpu
    if synchronize is not None:
        cfg.synchronize_checkpoint_boundary = synchronize
    if profile is not None:
        cfg.profile = profile
    if cfg.cpu_checkpointing and not _OFFLOAD_SUPPORTED:
        logger.warning("cpu_checkpointing requested but this jax has no offload "
                       "checkpoint policies; falling back to plain remat")
        cfg.cpu_checkpointing = False
    _config = cfg
    _configured = True


def is_configured() -> bool:
    """Parity: ``checkpointing.is_configured`` (``:918``)."""
    return _configured


def reset() -> None:
    """Parity: ``checkpointing.reset`` (``:896``) — clears global state."""
    global _config, _configured
    _config = CheckpointConfig()
    _configured = False


# ----------------------------------------------------------------------- policies
def policy_from_config(cfg: Optional[CheckpointConfig] = None):
    """Map the config onto a ``jax.checkpoint`` policy (or None = save nothing)."""
    cfg = cfg or _config
    if cfg.cpu_checkpointing:
        # save dot outputs but park them in host memory between fwd and bwd —
        # the reference's checkpoint_in_cpu (":748" arg_cpu path), minus the
        # hand-rolled pinned-buffer management.
        if hasattr(jax.checkpoint_policies, "offload_dot_with_no_batch_dims"):
            return jax.checkpoint_policies.offload_dot_with_no_batch_dims(
                "device", "pinned_host")
    name = cfg.policy_name
    if name in (None, "nothing_saveable", "none"):
        return jax.checkpoint_policies.nothing_saveable
    pol = getattr(jax.checkpoint_policies, name, None)
    if pol is None:
        raise ValueError(f"unknown jax.checkpoint policy {name!r}")
    return pol


def _partition_saved(x, mp_axes: Sequence[str]):
    """Sharding-constrain a saved activation over the model-parallel axes.

    Parity: ``partition_activations`` (``checkpointing.py:372``) — each MP rank keeps
    1/mp of every saved tensor; XLA re-gathers at recompute time.
    """
    if not isinstance(x, jax.Array) and not isinstance(x, jnp.ndarray):
        return x
    if x.ndim == 0:
        return x
    from jax.sharding import PartitionSpec as P

    # shard the first dimension divisible by the mp extent; bare specs resolve
    # against the ambient mesh (engine runs under mesh_context)
    try:
        axis_env = jax.sharding.get_abstract_mesh()  # jax>=0.4.35
        sizes = dict(zip(axis_env.axis_names, axis_env.axis_sizes)) if axis_env else {}
    except Exception:  # pragma: no cover - older jax
        sizes = {}
    live = [a for a in mp_axes if sizes.get(a, 1) > 1]
    if not live:
        return x
    extent = 1
    for a in live:
        extent *= sizes[a]
    # prefer trailing (feature/sequence) dims and never dim 0 of a batched
    # activation: dim 0 is the batch, already sharded over dp — constraining it
    # to the mp axes would force reshard collectives at every boundary instead
    # of reducing per-rank saved memory
    candidates = range(x.ndim - 1, 0, -1) if x.ndim >= 2 else range(x.ndim)
    for d in candidates:
        if x.shape[d] % extent == 0 and x.shape[d] >= extent:
            spec = [None] * x.ndim
            spec[d] = tuple(live) if len(live) > 1 else live[0]
            return jax.lax.with_sharding_constraint(x, P(*spec))
    return x


# ----------------------------------------------------------------------- API
def checkpoint(function: Callable, *args) -> Any:
    """Checkpoint ``function(*args)``: recompute its activations in backward.

    Parity: ``checkpointing.checkpoint`` (``:748``). Under the configured options
    this also partitions (shards) or host-offloads whatever the policy saves.
    """
    wrapped = checkpoint_wrapper(function)
    return wrapped(*args)


def checkpoint_wrapper(function: Callable,
                       cfg: Optional[CheckpointConfig] = None) -> Callable:
    """Return a rematerialized version of ``function``; composable with jit/scan."""
    cfg = cfg or _config
    policy = policy_from_config(cfg)

    if cfg.partition_activations:
        # wrap so that everything the policy saves is sharding-constrained over
        # the mp axes: apply constraint to the function outputs feeding residuals.
        inner = function

        def function(*a, **k):
            out = inner(*a, **k)
            return jax.tree_util.tree_map(
                lambda t: _partition_saved(t, cfg.mp_axes), out)

    remat = jax.checkpoint(function, policy=policy)

    if cfg.profile:
        @functools.wraps(function)
        def profiled(*a, **k):
            with jax.named_scope("activation_checkpoint"):
                return remat(*a, **k)

        return profiled
    return remat


# ----------------------------------------------------------------------- RNG tracker
class RNGStatesTracker:
    """Named PRNG-key tracker. Parity: ``CudaRNGStatesTracker`` (``:122``).

    In JAX, keys are values, so 'state save/restore around recompute' is automatic.
    What survives from the reference is the *naming* discipline: a
    ``model-parallel-rng`` stream derived per-MP-rank so dropout differs across tp
    ranks while data-parallel replicas agree (``:210-258``).
    """

    def __init__(self):
        self.states = {}

    def reset(self):
        self.states = {}

    def get_states(self):
        return dict(self.states)

    def set_states(self, states):
        self.states = dict(states)

    def add(self, name: str, seed: int):
        if name in self.states:
            raise Exception(f"RNG state {name} already exists")
        self.states[name] = jax.random.PRNGKey(seed)

    def fork(self, name: str = "model-parallel-rng"):
        """Split and return a fresh key from the named stream."""
        if name not in self.states:
            raise Exception(f"RNG state {name} not added")
        self.states[name], sub = jax.random.split(self.states[name])
        return sub


_tracker = RNGStatesTracker()


def get_rng_tracker() -> RNGStatesTracker:
    """Parity: ``get_cuda_rng_tracker`` (``:253``)."""
    return _tracker


def model_parallel_reseed(key: jax.Array, axis_name: str = "tp") -> jax.Array:
    """Fold the model-parallel coordinate into ``key`` (inside shard_map/pjit) so
    each tp rank draws distinct dropout. Parity:
    ``model_parallel_cuda_manual_seed`` (``:226``)."""
    try:
        idx = jax.lax.axis_index(axis_name)
    except NameError:
        return key
    return jax.random.fold_in(key, idx)
