from .checkpointing import (  # noqa: F401
    CheckpointConfig,
    checkpoint,
    checkpoint_wrapper,
    configure,
    get_rng_tracker,
    is_configured,
    model_parallel_reseed,
    policy_from_config,
    reset,
)
